"""Hirschberg Pallas aligner (ops/align_pallas.py) in interpret mode:
the emitted op path must be a valid alignment whose cost equals the true
(unbanded) edit distance whenever the optimal path stays in band.
"""

import random

import numpy as np
import pytest

from racon_tpu import native
from racon_tpu.ops import align_pallas
from racon_tpu.ops.encoding import encode
from tests.test_align import mutate


def path_cost(ops: np.ndarray, q: bytes, t: bytes) -> int:
    """Edit cost of the forward-ordered op path (0=M, 1=I, 2=D)."""
    cost = 0
    qi = ti = 0
    for op in ops:
        if op == 0:
            cost += q[qi] != t[ti]
            qi += 1
            ti += 1
        elif op == 1:
            cost += 1
            qi += 1
        else:
            cost += 1
            ti += 1
    assert qi == len(q) and ti == len(t), (qi, len(q), ti, len(t))
    return cost


def _align_one(q: bytes, t: bytes):
    res = align_pallas.align_pairs(
        [(encode(np.frombuffer(q, np.uint8)).astype(np.int32),
          encode(np.frombuffer(t, np.uint8)).astype(np.int32))],
        interpret=True)
    return res[0]


def _rand(rng, n):
    return bytes(rng.choice(b"ACGT") for _ in range(n))


def test_base_case_exact():
    rng = random.Random(1)
    q = _rand(rng, 200)
    t = mutate(q, 0.10, rng)
    ops = _align_one(q, t)
    assert ops is not None
    assert path_cost(ops, q, t) == native.edit_distance(q, t)


def test_multi_round_split_exact():
    rng = random.Random(2)
    q = _rand(rng, 1400)
    t = mutate(q, 0.08, rng)
    ops = _align_one(q, t)
    assert ops is not None
    assert path_cost(ops, q, t) == native.edit_distance(q, t)


def test_identical_pair_all_match():
    rng = random.Random(3)
    q = _rand(rng, 700)
    ops = _align_one(q, q)
    assert ops is not None
    assert (ops == 0).all()
    assert len(ops) == len(q)


def test_length_skew_within_band():
    rng = random.Random(4)
    q = _rand(rng, 900)
    t = q[:400] + q[520:]  # 120-base deletion
    ops = _align_one(q, t)
    assert ops is not None
    assert path_cost(ops, q, t) == native.edit_distance(q, t)


def test_oversize_band_goes_to_host():
    q = b"A" * 100
    t = b"A" * 3000  # drift beyond the largest band bucket
    assert _align_one(q, t) is None


def test_polish_with_hirschberg_engine(tmp_path, monkeypatch):
    """RACON_TPU_DEVICE_ALIGNER=hirschberg serves the PAF alignment phase
    through the Pallas engine end-to-end; consensus matches the
    host-aligned run within tie-break noise."""
    import racon_tpu

    rng = random.Random(11)
    truth = "".join(rng.choice("ACGT") for _ in range(400))

    def mut(s, rate):
        out = []
        for c in s:
            r = rng.random()
            if r < rate / 2:
                out.append(rng.choice("ACGT"))
            elif r < rate:
                continue
            else:
                out.append(c)
        return "".join(out)

    draft = mut(truth, 0.02)
    reads = [mut(truth, 0.05) for _ in range(5)]
    with open(tmp_path / "t.fasta", "w") as f:
        f.write(f">t\n{draft}\n")
    with open(tmp_path / "r.fasta", "w") as rf, \
            open(tmp_path / "o.paf", "w") as of:
        for i, r in enumerate(reads):
            rf.write(f">r{i}\n{r}\n")
            of.write(f"r{i}\t{len(r)}\t0\t{len(r)}\t+\tt\t{len(draft)}\t0\t"
                     f"{len(draft)}\t{min(len(r), len(draft))}\t"
                     f"{max(len(r), len(draft))}\t60\n")

    def run(engine):
        monkeypatch.setenv("RACON_TPU_DEVICE_ALIGNER", engine)
        p = racon_tpu.TpuPolisher(str(tmp_path / "r.fasta"),
                                  str(tmp_path / "o.paf"),
                                  str(tmp_path / "t.fasta"),
                                  window_length=100, match=5, mismatch=-4,
                                  gap=-8)
        p.initialize()
        return p.polish(True)

    dev = run("hirschberg")
    host = run("0")
    assert len(dev) == len(host) == 1
    d = native.edit_distance(dev[0][1].encode(), host[0][1].encode())
    assert d <= 2, d
    assert native.edit_distance(dev[0][1].encode(), truth.encode()) <= 8


def test_sharded_batches_over_mesh_exact(monkeypatch):
    """A homogeneous batch that divides the 8-device mesh runs the edge
    and base kernels under shard_map (the consensus path's no-collective
    batch striping) and must emit the same exact-optimal paths as the
    single-device build."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the suite's 8-virtual-device mesh")

    shard_calls = []
    real = align_pallas._shard_over_mesh

    def recording(build_local, batch, n_in, n_out):
        out = real(build_local, batch, n_in, n_out)
        shard_calls.append((batch, out is not None))
        return out

    monkeypatch.setattr(align_pallas, "_shard_over_mesh", recording)
    # fresh builders so cached single-device jits can't bypass the recorder
    align_pallas._build_edge_kernel.cache_clear()
    align_pallas._build_base_kernel.cache_clear()

    rng = random.Random(23)
    pairs = []
    for _ in range(8):  # homogeneous bucket: same lengths -> same (rcap, K)
        q = _rand(rng, 700)
        t = mutate(q, 0.06, rng)
        pairs.append((q, t))
    enc = [(encode(np.frombuffer(q, np.uint8)).astype(np.int32),
            encode(np.frombuffer(t, np.uint8)).astype(np.int32))
           for q, t in pairs]
    results = align_pallas.align_pairs(enc, interpret=True)

    assert any(ok for _, ok in shard_calls), shard_calls  # mesh engaged
    for (q, t), ops in zip(pairs, results):
        assert ops is not None
        assert path_cost(ops, q, t) == native.edit_distance(q, t)

    align_pallas._build_edge_kernel.cache_clear()
    align_pallas._build_base_kernel.cache_clear()


def test_engine_auto_defaults_to_hirschberg_on_tpu(monkeypatch):
    """With no env override, the production tier is the Hirschberg engine
    on a TPU backend and the host Myers aligner elsewhere — the same
    device-on-TPU posture as the consensus path."""
    from racon_tpu.ops import align_driver

    monkeypatch.delenv("RACON_TPU_DEVICE_ALIGNER", raising=False)
    monkeypatch.setattr(align_driver, "_on_tpu", lambda: True)
    assert align_driver._engine() == "hirschberg"
    monkeypatch.setattr(align_driver, "_on_tpu", lambda: False)
    assert align_driver._engine() == "host"
    monkeypatch.setenv("RACON_TPU_DEVICE_ALIGNER", "host")
    monkeypatch.setattr(align_driver, "_on_tpu", lambda: True)
    assert align_driver._engine() == "host"


def test_engine_failure_degrades_to_host(tmp_path, monkeypatch):
    """A hirschberg kernel failure mid-phase must not abort the polish:
    the remaining jobs stay CIGAR-less and the host aligner finishes
    them, mirroring the consensus driver's degrade lattice."""
    import racon_tpu
    from racon_tpu.ops import align_driver, align_pallas as ap

    rng = random.Random(17)
    truth = "".join(rng.choice("ACGT") for _ in range(300))
    reads = [truth for _ in range(3)]
    with open(tmp_path / "t.fasta", "w") as f:
        f.write(f">t\n{truth}\n")
    with open(tmp_path / "r.fasta", "w") as rf, \
            open(tmp_path / "o.paf", "w") as of:
        for i, r in enumerate(reads):
            rf.write(f">r{i}\n{r}\n")
            of.write(f"r{i}\t{len(r)}\t0\t{len(r)}\t+\tt\t{len(truth)}\t0\t"
                     f"{len(truth)}\t{len(r)}\t{len(r)}\t60\n")

    def boom(pairs, *, interpret=None):
        raise RuntimeError("synthetic Mosaic failure")

    monkeypatch.setenv("RACON_TPU_DEVICE_ALIGNER", "hirschberg")
    monkeypatch.setattr(ap, "align_pairs", boom)
    p = racon_tpu.TpuPolisher(str(tmp_path / "r.fasta"),
                              str(tmp_path / "o.paf"),
                              str(tmp_path / "t.fasta"),
                              window_length=100, match=5, mismatch=-4,
                              gap=-8)
    p.initialize()
    res = p.polish(True)
    assert len(res) == 1
    assert res[0][1] == truth

    # and the driver's stats record the degrade: nothing device-served
    pipe = racon_tpu.pipeline.Pipeline(
        str(tmp_path / "r.fasta"), str(tmp_path / "o.paf"),
        str(tmp_path / "t.fasta"), window_length=100, match=5,
        mismatch=-4, gap=-8)
    pipe.prepare()
    stats = align_driver.run_alignment_phase(pipe)
    assert stats["device"] == 0
    assert stats["host"] == pipe.num_align_jobs()


def test_cigar_roundtrip():
    rng = random.Random(5)
    q = _rand(rng, 300)
    t = mutate(q, 0.1, rng)
    ops = _align_one(q, t)
    cigar = align_pallas.ops_to_cigar(ops)
    qc = tc = 0
    num = ""
    for ch in cigar:
        if ch.isdigit():
            num += ch
        else:
            n = int(num)
            num = ""
            if ch in "MI":
                qc += n
            if ch in "MD":
                tc += n
    assert qc == len(q) and tc == len(t)


@pytest.mark.parametrize("seed", [31, 62])
def test_hirschberg_fuzz_exact(seed):
    """Seeded random pairs across the length/error envelope phase 1
    serves (short fragments up to multi-kb reads, 2-18% divergence,
    length skew): every emitted path must be valid and cost-optimal;
    None (band escape / oversize) is acceptable only where the band
    rule says so."""
    rng = random.Random(seed)
    pairs = []
    for _ in range(6):
        n = rng.randrange(60, 2500)
        q = _rand(rng, n)
        t = mutate(q, rng.uniform(0.02, 0.18), rng)
        pairs.append((q, t))
    enc = [(encode(np.frombuffer(q, np.uint8)).astype(np.int32),
            encode(np.frombuffer(t, np.uint8)).astype(np.int32))
           for q, t in pairs]
    results = align_pallas.align_pairs(enc, interpret=True)
    n_served = 0
    for (q, t), ops in zip(pairs, results):
        if ops is None:
            continue
        n_served += 1
        assert path_cost(ops, q, t) == native.edit_distance(q, t), \
            (seed, len(q), len(t))
    assert n_served >= len(pairs) - 1, "band escapes should be rare here"
