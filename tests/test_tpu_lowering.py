"""Cross-lower every production Pallas kernel to TPU without hardware.

jax.export with platforms=['tpu'] runs the full Pallas -> Mosaic lowering
pipeline on the CPU backend. Interpret-mode tests (the rest of the suite)
execute kernels as plain XLA and silently accept constructs Mosaic cannot
lower — round 4 caught exactly that: the v3 kernel's dynamic extract used
lax.dynamic_slice on a loaded value, which interpret mode runs fine and
TPU lowering rejects outright. This gate would have burned a scarce
healthy-tunnel session to discover.

(What it cannot catch: Mosaic *compile*-stage failures — layout/VMEM
pressure — and runtime miscompiles; those remain the hardware session's
job. Lowering errors are the big first-order class.)

Reference analogue: building the CUDA kernels is part of the reference's
default build+test cycle (CMakeLists racon_enable_cuda), so a
non-compiling kernel cannot land there either.
"""

import numpy as np
import pytest

import jax
# Not eagerly imported by jax/__init__ on 0.4.x — without this the
# attribute lookup below hits the deprecation __getattr__ and raises.
import jax.export

from racon_tpu.ops import align_pallas, poa_driver


def _mosaic_lowers_int_reductions():
    """Capability probe: the production kernels reduce over int32 DP
    state, which older Mosaic pipelines reject wholesale
    ("Reductions over integers not implemented").  On such a toolchain
    this gate cannot run at all — skip with the real reason rather than
    failing every kernel on the same missing backend feature.  Any
    OTHER probe failure returns True so the tests still run and surface
    it loudly."""
    from jax.experimental import pallas as pl
    import jax.numpy as jnp

    def k(x_ref, o_ref):
        o_ref[0, 0] = jnp.max(x_ref[...])

    fn = pl.pallas_call(
        k, out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32))
    try:
        jax.export.export(jax.jit(fn), platforms=["tpu"])(
            np.zeros((8, 128), np.int32))
        return True
    except Exception as e:
        return "Reductions over integers" not in str(e)


pytestmark = pytest.mark.skipif(
    not _mosaic_lowers_int_reductions(),
    reason="this jax's Mosaic cannot lower integer reductions; "
           "the TPU-lowering gate needs a newer toolchain")


def _export_tpu(fn, args):
    return jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)


def _poa_args(cfg, B, rng):
    import __graft_entry__ as g

    bb, bbw, bl, nl, seqs, ws, lens, bg, en = g._example_batch(cfg, B, rng)
    return (bl.reshape(-1, 1), nl.reshape(-1, 1), lens, bg, en,
            bb.astype(np.int32), bbw, seqs.astype(np.int32), ws)


@pytest.mark.parametrize("window_length", [100, 500, 1000])
def test_lockstep_poa_kernel_lowers_to_tpu(window_length):
    """All production geometries: w=100 (small-window datasets), w=500
    (default), w=1000 (the paf_w1000 golden scenario). The VMEM-fit model
    must agree — a geometry _fits_vmem approves has to actually lower."""
    from racon_tpu.ops.poa_pallas_ls import build_lockstep_poa_kernel

    cfg = poa_driver.make_config(window_length, 8, 5, -4, -8)
    assert poa_driver._fits_vmem(cfg, "ls"), "fit model rejects geometry"
    fn = build_lockstep_poa_kernel(cfg, interpret=False)(8)
    exp = _export_tpu(fn, _poa_args(cfg, 8, np.random.default_rng(0)))
    assert len(exp.mlir_module_serialized) > 0


def test_lockstep_poa_kernel_lowers_at_node_factor_4(monkeypatch):
    """The hw_session factor4 step (RACON_TPU_NODE_FACTOR=4, admits the
    repeat-dense windows factor 3 rejects — interpret evidence: 96/96 λ
    windows device-served at ed 1282) must not be blocked by an
    unlowerable geometry. v2 no longer fits VMEM at factor 4, so ls is
    the only pallas tier there — all the more reason to gate it here."""
    from racon_tpu.ops.poa_pallas_ls import build_lockstep_poa_kernel

    monkeypatch.setenv("RACON_TPU_NODE_FACTOR", "4")
    cfg = poa_driver.make_config(500, 8, 5, -4, -8)
    assert cfg.max_nodes == 2048
    assert poa_driver._fits_vmem(cfg, "ls"), "fit model rejects geometry"
    fn = build_lockstep_poa_kernel(cfg, interpret=False)(8)
    exp = _export_tpu(fn, _poa_args(cfg, 8, np.random.default_rng(0)))
    assert len(exp.mlir_module_serialized) > 0


def test_v2_poa_kernel_lowers_to_tpu():
    from racon_tpu.ops.poa_pallas import build_pallas_poa_kernel

    cfg = poa_driver.make_config(500, 8, 5, -4, -8)
    fn = build_pallas_poa_kernel(cfg, interpret=False)(2)
    exp = _export_tpu(fn, _poa_args(cfg, 2, np.random.default_rng(0)))
    assert len(exp.mlir_module_serialized) > 0


def test_hirschberg_edge_kernels_lower_to_tpu():
    rcap, K, B = 512, 128, 2
    scal = np.zeros((B, 4), np.int32)
    scal[:, 0] = rcap
    scal[:, 1] = rcap + K
    qs = np.zeros((B, rcap), np.int32)
    ts = np.full((B, rcap + K), 255, np.int32)
    for backward in (False, True):
        fn = align_pallas._build_edge_kernel(rcap, K, backward,
                                             interpret=False)(B)
        exp = _export_tpu(fn, (scal, qs, ts))
        assert len(exp.mlir_module_serialized) > 0


def test_hirschberg_base_kernel_lowers_to_tpu():
    K, B = 128, 2
    kern, OPS, QCAP, TCAP = align_pallas._build_base_kernel(
        K, interpret=False)
    scal = np.zeros((B, 4), np.int32)
    scal[:, 0] = 1
    qs = np.zeros((B, QCAP), np.int32)
    ts = np.full((B, TCAP), 255, np.int32)
    exp = _export_tpu(kern(B), (scal, qs, ts))
    assert len(exp.mlir_module_serialized) > 0
