"""Observability layer: span tracer + metrics registry + CLI.

Covers the contracts docs/observability.md promises: thread-safe span
nesting, Chrome-trace schema validity of a real traced polish with all
five phase spans, the served-sum invariant (metrics counters vs the run
report, cross-checked — not assumed), byte-identical polished output
armed vs disarmed (and no trace file when disarmed), the CLI's four
exit codes, and the align-driver accounting regression: a mid-cohort
engine death after partial CIGAR installs must not erase the
device-served count.
"""

import json
import random
import threading

import pytest

import racon_tpu
from racon_tpu import obs
from racon_tpu.obs import __main__ as obs_cli
from racon_tpu.obs.metrics import Histogram, Metrics, hist_quantile
from racon_tpu.obs.tracer import NULL_SPAN, Tracer


@pytest.fixture(autouse=True)
def _disarm_after():
    """Module-level obs state must never leak between tests."""
    yield
    obs.reset()


# ------------------------------------------------------------ unit: tracer

def test_tracer_thread_pool_nesting():
    tr = Tracer()
    # the barrier keeps all 8 threads alive at once: Python reuses
    # thread idents of finished threads, which would fold the per-thread
    # name metadata this test asserts on
    gate = threading.Barrier(8)

    def work(k):
        gate.wait()
        # µs-aligned ns stamps: _ts_us floor-divides (t - epoch) by 1000,
        # so sub-µs offsets would make the rounded nesting depend on the
        # epoch's ns remainder (and the durations collapse to 0)
        t0 = 1_000_000 * k
        tr.add_complete(f"outer.{k}", t0, t0 + 500_000, idx=k)
        tr.add_complete(f"inner.{k}", t0 + 100_000, t0 + 200_000)
        gate.wait()

    threads = [threading.Thread(target=work, args=(k,), name=f"w{k}")
               for k in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    events = tr.events()
    assert len(events) == 16
    # every event carries its recording thread's tid, and each thread's
    # inner span nests inside its outer span on the same timeline row
    by_name = {e["name"]: e for e in events}
    for k in range(8):
        outer, inner = by_name[f"outer.{k}"], by_name[f"inner.{k}"]
        assert outer["tid"] == inner["tid"]
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"]
    # thread-name metadata rides along in the written document
    doc = tr.to_dict()
    names = {e["args"]["name"] for e in doc["traceEvents"]
             if e.get("ph") == "M"}
    assert {f"w{k}" for k in range(8)} <= names


def test_tracer_bounded_buffer():
    tr = Tracer(max_events=3)
    for k in range(5):
        tr.add_instant(f"e{k}")
    assert len(tr.events()) == 3 and tr.dropped == 2
    assert tr.to_dict()["otherData"]["dropped_events"] == 2


def test_span_records_error_on_exception():
    tr = Tracer()
    with pytest.raises(RuntimeError):
        with obs.Span(tr, "boom", {}):
            raise RuntimeError("x")
    (ev,) = tr.events()
    assert ev["args"]["error"] == "RuntimeError" and ev["dur"] >= 0


# ----------------------------------------------------------- unit: metrics

def test_metrics_counters_and_prefix_sum():
    m = Metrics()
    m.count("served.consensus.ls", 3)
    m.count("served.consensus.host")
    m.count("served.alignment.host", 7)
    assert m.counter("served.consensus.ls") == 3
    assert m.prefix_sum("served.consensus.") == 4
    assert m.prefix_sum("served.") == 11


def test_histogram_log2_buckets():
    h = Histogram()
    for v in (0.0, 0.5, 1.0, 3.0, 1000.0):
        h.observe(v)
    d = h.as_dict()
    assert d["count"] == 5 and d["min"] == 0.0 and d["max"] == 1000.0
    assert d["buckets"] == {"0": 1, "1": 2, "4": 1, "1024": 1}


# ----------------------------------------------------- unit: armed/disarmed

def test_disarmed_hooks_are_noops():
    obs.reset()
    assert not obs.enabled()
    assert obs.span("anything", k=1) is NULL_SPAN
    obs.event("x")        # must not raise
    obs.count("x")
    obs.observe("x", 1.0)
    assert obs.snapshot() is None
    assert obs.write_trace() is None


def test_configure_metrics_only_collects_without_file(tmp_path):
    obs.reset()
    obs.configure(metrics=True)
    assert obs.enabled() and obs.trace_path() is None
    with obs.span("s"):
        obs.count("c", 2)
    assert obs.snapshot()["counters"] == {"c": 2}
    assert obs.write_trace() is None   # no path configured
    obs.reset()


# ------------------------------------------------------------ e2e fixtures

def _write_dataset(tmp_path, n_targets=3, n_reads=4):
    """Identical-read PAF dataset (no CIGARs, so phase 1 has real align
    jobs): device- and host-served results are byte-comparable."""
    rng = random.Random(11)
    with open(tmp_path / "targets.fasta", "w") as tf, \
            open(tmp_path / "reads.fasta", "w") as rf, \
            open(tmp_path / "ovl.paf", "w") as of:
        for t in range(n_targets):
            seq = "".join(rng.choice("ACGT") for _ in range(200))
            tf.write(f">t{t}\n{seq}\n")
            for i in range(n_reads):
                rf.write(f">t{t}r{i}\n{seq}\n")
                of.write(f"t{t}r{i}\t200\t0\t200\t+\tt{t}\t200\t0\t200"
                         f"\t200\t200\t60\n")
    return (str(tmp_path / "reads.fasta"), str(tmp_path / "ovl.paf"),
            str(tmp_path / "targets.fasta"))


_ARGS = dict(window_length=100, quality_threshold=10, error_threshold=0.3,
             match=5, mismatch=-4, gap=-8, num_threads=1)


def _tpu_run(paths, monkeypatch, env, **kwargs):
    base = {"RACON_TPU_PALLAS": "0", "RACON_TPU_POA_KERNEL": "v2",
            "RACON_TPU_BATCH_WINDOWS": "8"}
    for k, v in {**base, **env}.items():
        monkeypatch.setenv(k, v)
    p = racon_tpu.create_polisher(*paths, backend="tpu", **_ARGS, **kwargs)
    p.initialize()
    res = p.polish(True)
    return res, p


# --------------------------------------------------- e2e: traced tpu polish

def test_traced_polish_trace_schema_and_phases(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path)
    trace = tmp_path / "run_trace.json"
    res, p = _tpu_run(paths, monkeypatch,
                      {"RACON_TPU_DEVICE_ALIGNER": "1"},
                      trace_path=str(trace))
    assert res and trace.exists()
    doc, errors = obs_cli.load_trace(str(trace))
    assert errors == [], errors
    # all five pipeline phases appear as phase.* complete events
    walls = obs_cli.phase_walls_us(doc)
    assert set(obs.PHASES) <= set(walls), walls
    # served-sum invariant: the served.* counters embedded in the trace
    # reconcile exactly with the run report's per-phase served totals
    b = obs_cli.breakdown(doc)
    d = p.report.as_dict()
    for phase, rep in d["phases"].items():
        assert sum(b["served"][phase].values()) == rep["total"], (phase, b)
    assert d["obs"]["armed"] is True
    assert all(v["ok"] for v in d["obs"]["served_sum"].values()), d["obs"]
    # the report summary carries the per-phase tier walls bench.py stamps
    for rep in p.report.summary().values():
        if isinstance(rep, dict):
            assert "wall_s" in rep


def test_disarmed_polish_byte_identical_no_trace(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path)
    monkeypatch.delenv("RACON_TPU_TRACE", raising=False)
    monkeypatch.delenv("RACON_TPU_METRICS", raising=False)
    plain, p_plain = _tpu_run(paths, monkeypatch, {})
    assert not obs.enabled()
    assert p_plain.report.as_dict()["obs"] == {"armed": False}
    trace = tmp_path / "armed_trace.json"
    traced, _ = _tpu_run(paths, monkeypatch, {}, trace_path=str(trace))
    assert traced == plain          # observability never changes output
    assert trace.exists()
    assert not (tmp_path / "ghost.json").exists()
    # disarmed run again (fresh polisher resets obs): still no stray file
    replain, _ = _tpu_run(paths, monkeypatch, {})
    assert replain == plain
    assert list(tmp_path.glob("*.json")) == [trace]


def test_env_knob_arms_tracing(tmp_path, monkeypatch):
    paths = _write_dataset(tmp_path)
    trace = tmp_path / "env_trace.json"
    res, _ = _tpu_run(paths, monkeypatch,
                      {"RACON_TPU_TRACE": str(trace)})
    assert res and trace.exists()
    doc, errors = obs_cli.load_trace(str(trace))
    assert errors == []
    assert doc["racon_tpu"]["metrics"]["counters"]


# ------------------------------------- e2e: align accounting under faults

def test_partial_install_death_keeps_device_count(tmp_path, monkeypatch):
    """Regression (satellite): the xla engine dying mid-cohort AFTER some
    CIGARs were installed must keep those jobs counted as device-served —
    the old `stats["device"] = run_jobs(...)` assignment lost them all,
    over-reporting the host share."""
    paths = _write_dataset(tmp_path)        # 12 align jobs, all eligible
    oracle_p = racon_tpu.create_polisher(*paths, backend="cpu", **_ARGS)
    oracle_p.initialize()
    oracle = oracle_p.polish(True)
    res, p = _tpu_run(paths, monkeypatch, {
        "RACON_TPU_DEVICE_ALIGNER": "1",
        "RACON_TPU_FAULT": "align.install:window=5",
    })
    assert res == oracle            # host finished the rest, byte-equal
    d = p.report.as_dict()
    align_rep = d["phases"]["alignment"]
    # jobs 0..4 were installed before the fault on job 5 killed the
    # engine: they must survive as device-served
    assert align_rep["served"].get("xla") == 5, align_rep
    assert sum(align_rep["served"].values()) == align_rep["total"]
    assert align_rep["degradations"], "engine death must be recorded"


# -------------------------------------------------------------- CLI: exits

def _trace_doc(poa_us):
    return {"traceEvents": [
        {"name": "phase.poa", "ph": "X", "ts": 0, "dur": poa_us,
         "pid": 1, "tid": 1, "args": {}},
        {"name": "phase.stitch", "ph": "X", "ts": poa_us, "dur": 10,
         "pid": 1, "tid": 1, "args": {}},
    ]}


def test_cli_exit_0_valid(tmp_path, capsys):
    path = tmp_path / "t.json"
    path.write_text(json.dumps(_trace_doc(5000)))
    assert obs_cli.main([str(path)]) == 0
    assert "phase" in capsys.readouterr().out
    assert obs_cli.main(["--validate", str(path)]) == 0


def test_cli_exit_1_schema_violation(tmp_path):
    doc = _trace_doc(5000)
    doc["traceEvents"].append({"name": "bad", "ph": "Z", "ts": 0,
                               "pid": 1, "tid": 1})
    doc["traceEvents"].append({"name": "", "ph": "X", "ts": -1, "dur": -2,
                               "pid": "x", "tid": 1})
    path = tmp_path / "bad.json"
    path.write_text(json.dumps(doc))
    assert obs_cli.main(["--validate", str(path)]) == 1


def test_cli_exit_2_unreadable(tmp_path):
    assert obs_cli.main([str(tmp_path / "missing.json")]) == 2
    notjson = tmp_path / "notjson.json"
    notjson.write_text("{nope")
    assert obs_cli.main([str(notjson)]) == 2
    nottrace = tmp_path / "nottrace.json"
    nottrace.write_text(json.dumps({"hello": 1}))
    assert obs_cli.main([str(nottrace)]) == 2
    # argument errors are exit 2 as well
    assert obs_cli.main(["--diff", str(notjson)]) == 2


def test_cli_exit_3_diff_regression(tmp_path):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(_trace_doc(10_000)))
    new.write_text(json.dumps(_trace_doc(20_000)))
    assert obs_cli.main(["--diff", str(old), str(new)]) == 3
    # within threshold (or shrinking): no regression
    assert obs_cli.main(["--diff", str(old), str(old)]) == 0
    assert obs_cli.main(["--diff", str(new), str(old)]) == 0
    # huge relative growth under --min-delta-us is noise, not regression
    assert obs_cli.main(["--diff", str(old), str(new),
                         "--min-delta-us", "50000"]) == 0


def test_cli_diff_json_output(tmp_path, capsys):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(_trace_doc(10_000)))
    new.write_text(json.dumps(_trace_doc(40_000)))
    assert obs_cli.main(["--diff", "--json", str(old), str(new)]) == 3
    out = json.loads(capsys.readouterr().out)
    assert any("phase.poa" in r for r in out["regressions"])


def test_cli_diff_one_sided_phase_is_flagged_not_crashed(tmp_path, capsys):
    """Satellite: a phase present on only one side (a resumed run that
    replayed align from the journal has no phase.align span) is flagged
    only-in-old/new with the missing side counted as 0 — previously
    infinite-percent material."""
    both = tmp_path / "both.json"
    both.write_text(json.dumps(_trace_doc(10_000)))
    doc = _trace_doc(10_000)
    doc["traceEvents"].append({"name": "phase.align", "ph": "X", "ts": 0,
                               "dur": 50_000, "pid": 1, "tid": 1})
    extra = tmp_path / "extra.json"
    extra.write_text(json.dumps(doc))
    # phase only in OLD: not a regression (new side is 0), just a note
    assert obs_cli.main(["--diff", str(extra), str(both)]) == 0
    out = capsys.readouterr().out
    assert "only-in-old" in out and "phase.align" in out
    # phase only in NEW past min-delta: flagged AND gated as a regression
    assert obs_cli.main(["--diff", str(both), str(extra)]) == 3
    out = capsys.readouterr().out
    assert "only-in-new" in out
    assert obs_cli.main(["--diff", "--json", str(both), str(extra)]) == 3
    j = json.loads(capsys.readouterr().out)
    assert any("only-in-new" in f for f in j["only_in"])
    assert any("only-in-new" in r for r in j["regressions"])
    # under min-delta the structural note stays but nothing gates
    assert obs_cli.main(["--diff", str(both), str(extra),
                         "--min-delta-us", "60000"]) == 0


def test_cli_validate_reports_dropped_events(tmp_path, capsys):
    doc = _trace_doc(5000)
    doc["otherData"] = {"dropped_events": 12}
    path = tmp_path / "t.json"
    path.write_text(json.dumps(doc))
    assert obs_cli.main(["--validate", str(path)]) == 0
    out = capsys.readouterr().out
    assert "12 event(s)" in out and "truncated" in out
    assert obs_cli.main(["--validate", "--json", str(path)]) == 0
    assert json.loads(capsys.readouterr().out)["dropped_events"] == 12
    # the breakdown warns too
    assert obs_cli.main([str(path)]) == 0
    assert "dropped" in capsys.readouterr().out


# ------------------------------- e2e: span quantiles + cell counters

def test_traced_polish_span_quantiles_and_cost_counters(tmp_path,
                                                        monkeypatch):
    """The on_complete callback feeds span_us.* histograms for every
    finished span (buffer-dropped ones included), the drivers count the
    measured DP cells the cost model predicts against, and the platform
    provenance stamp lands in otherData."""
    paths = _write_dataset(tmp_path)
    trace = tmp_path / "q_trace.json"
    res, _ = _tpu_run(paths, monkeypatch,
                      {"RACON_TPU_DEVICE_ALIGNER": "1"},
                      trace_path=str(trace))
    assert res
    doc, errors = obs_cli.load_trace(str(trace))
    assert errors == []
    q = obs_cli.span_quantiles(doc)
    for phase in obs.PHASES:
        name = f"phase.{phase}"
        assert name in q, (name, sorted(q))
        assert q[name]["count"] >= 1
        assert 0 <= q[name]["p50_us"] <= q[name]["p99_us"]
    assert "span durations" in obs_cli.render(doc, str(trace))
    counters = doc["racon_tpu"]["metrics"]["counters"]
    assert any(k.startswith("poa.cells.d") for k in counters), counters
    assert "align.cells.total" in counters
    assert doc["otherData"]["platform"] == "cpu"
    # the measured-cell counters drive a structurally complete validation
    from racon_tpu.obs import costmodel
    v = costmodel.validate_trace(doc, costmodel.PROFILES["cpu-host"])
    assert set(v["phases"]) == {"poa", "align"}
    assert v["phases"]["poa"]["predicted_s"] > 0.0
    assert any(b["kind"] == "poa" for b in v["buckets"])


# --------------------------------------- fleet tracing: context + shipping

def test_trace_context_mint_child_activate():
    from racon_tpu.obs import context

    ctx = context.fresh()
    assert len(ctx["trace_id"]) == 16 and ctx["parent"] is None
    kid = context.child(ctx)
    assert kid["trace_id"] == ctx["trace_id"]
    assert len(kid["parent"]) == 8
    assert context.child(kid)["parent"] != kid["parent"]   # fresh per call
    assert context.child(None) is None

    context.activate(kid)
    assert context.current() == kid
    context.current()["parent"] = "mutated"        # returns a copy
    assert context.current() == kid
    context.activate({"trace_id": ""})             # invalid -> deactivated
    assert context.current() is None
    context.clear()


def test_configure_idempotent_and_scoped(tmp_path):
    """Satellite regression: re-configuring with the SAME trace path must
    keep the armed tracer (and its spans); a DIFFERENT path starts a
    fresh scope; release() disarms so spans cannot leak across scopes."""
    obs.reset()
    p1 = str(tmp_path / "a.json")
    obs.configure(trace_path=p1)
    with obs.span("first"):
        pass
    obs.configure(trace_path=p1)               # idempotent: same scope
    with obs.span("second"):
        pass
    names = {e["name"] for e in obs.tracer().events()}
    assert {"first", "second"} <= names

    p2 = str(tmp_path / "b.json")
    obs.configure(trace_path=p2)               # new scope: fresh tracer
    names2 = {e["name"] for e in obs.tracer().events()}
    assert "first" not in names2

    path = obs.release(write=True)
    assert path == p2
    assert not obs.enabled()                   # released scope is disarmed
    doc = json.load(open(p2))
    assert "first" not in {e.get("name") for e in doc["traceEvents"]}
    obs.reset()


def test_export_ingest_rebase_and_tracks(tmp_path):
    """A worker-side export absorbed by a coordinator-side tracer keeps
    its pid track, gets its timestamps re-based onto the absorber's
    epoch, and the merged document validates."""
    coord = Tracer()
    worker = Tracer()
    worker.pid = coord.pid + 1           # simulate a second process
    worker.role = "worker9"
    worker._t0 = coord.t0_ns + 2_000_000     # worker clock starts 2ms later
    worker.add_complete("distrib.chunk", worker.t0_ns,
                        worker.t0_ns + 1_000_000, chunk=0)
    ship = worker.export(max_events=10, metrics={"counters": {"c": 1}})
    assert ship["role"] == "worker9" and ship["metrics"]["counters"] == {"c": 1}

    assert coord.ingest(ship) == 1
    assert coord.ingest("garbage") == 0
    assert coord.ingest({"events": "nope"}) == 0
    doc = coord.to_dict()
    chunk = [e for e in doc["traceEvents"]
             if e.get("name") == "distrib.chunk"][0]
    assert chunk["pid"] == worker.pid
    assert chunk["ts"] == 2000               # re-based: 2ms offset in µs
    pnames = {(e["pid"], e["args"]["name"]) for e in doc["traceEvents"]
              if e.get("ph") == "M" and e["name"] == "process_name"}
    assert (worker.pid, "worker9") in pnames

    path = tmp_path / "merged_inline.json"
    path.write_text(json.dumps(doc))
    assert obs_cli.main(["--validate", str(path)]) == 0


def test_export_truncation_counts_dropped():
    t = Tracer()
    for i in range(5):
        t.add_complete(f"s{i}", 0, 1000)
    ship = t.export(max_events=2)
    assert len(ship["events"]) == 2
    assert ship["dropped"] == 3
    assert ship["events"][-1]["name"] == "s4"    # newest win


def test_cli_merge_rebases_and_fleet_checks(tmp_path):
    a = Tracer()
    a.role = "coordinator"
    a.add_instant("distrib.dispatch", span_id="cafe0001",
                  trace_id="ab" * 8)
    b = Tracer()
    b.pid = a.pid + 1
    b.role = "worker0"
    b._t0 = a.t0_ns + 5_000_000
    b.add_complete("distrib.chunk", b.t0_ns, b.t0_ns + 1000,
                   parent="cafe0001", trace_id="ab" * 8)
    pa, pb = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    a.write(pa)
    b.write(pb)
    merged = str(tmp_path / "m.json")
    assert obs_cli.main(["merge", "--out", merged, pb, pa]) == 0
    assert obs_cli.main(["--validate", merged]) == 0
    doc = json.load(open(merged))
    assert len(doc["racon_tpu"]["processes"]) == 2
    chunk = [e for e in doc["traceEvents"]
             if e.get("name") == "distrib.chunk"][0]
    assert chunk["ts"] == 5000           # worker epoch 5ms after base
    assert obs_cli.main(["fleet", merged]) == 0

    # drop the dispatch: the chunk's parent dangles -> exit 1
    doc["traceEvents"] = [e for e in doc["traceEvents"]
                          if e.get("name") != "distrib.dispatch"]
    bad = str(tmp_path / "bad.json")
    json.dump(doc, open(bad, "w"))
    assert obs_cli.main(["fleet", bad]) == 1
    # unreadable stays exit 2
    assert obs_cli.main(["fleet", str(tmp_path / "missing.json")]) == 2
    assert obs_cli.main(["merge", "--out", merged,
                         str(tmp_path / "missing.json")]) == 2


# ------------------------------------------------- flight recorder + rings

def test_flight_recorder_ring_dump_and_scan(tmp_path, monkeypatch):
    from racon_tpu.obs.flight import FlightRecorder, scan

    fr = FlightRecorder(max_events=16)
    fr.set_role("testproc")
    for i in range(40):
        fr.record(f"ev{i}", step=i)
    assert fr.dump("nowhere") is None            # no dir set -> no dump

    sub = tmp_path / "chunks" / "chunk000"
    fr.set_dir(str(sub))
    path = fr.dump("unit_test", detail_key="v")
    doc = json.load(open(path))
    assert doc["reason"] == "unit_test" and doc["role"] == "testproc"
    assert len(doc["events"]) == 16              # ring capacity held
    assert doc["events"][-1]["name"] == "ev39"   # newest kept
    assert doc["detail"] == {"detail_key": "v"}

    # recursive scan finds nested dumps and skips torn files
    (tmp_path / "flight.999.json").write_text("{torn")
    docs = scan(str(tmp_path))
    assert len(docs) == 1 and docs[0]["path"] == path

    monkeypatch.setenv("RACON_TPU_FLIGHT", "0")
    fr.record("ignored")
    assert fr.dump("disabled") is None           # knob gates dumping too


def test_obs_event_feeds_flight_even_disarmed(monkeypatch):
    from racon_tpu.obs import flight

    monkeypatch.delenv("RACON_TPU_FLIGHT", raising=False)
    obs.reset()
    assert not obs.enabled()
    obs.event("breadcrumb.disarmed", k=1)
    names = [e["name"] for e in flight.recorder()._ring]
    assert "breadcrumb.disarmed" in names


def test_telemetry_ring_bounded(monkeypatch):
    monkeypatch.setenv("RACON_TPU_TELEMETRY_RING", "4")
    obs.reset()
    import racon_tpu.obs as o
    o._telemetry = None                  # force re-size from the knob
    for i in range(10):
        entry = obs.telemetry_tick(queue_depth=i)
    assert entry["queue_depth"] == 9
    assert "t_mono_ns" in entry
    ring = obs.telemetry()
    assert len(ring) == 4                # bounded by the knob
    assert ring[-1]["queue_depth"] == 9
    assert obs.telemetry(last=2) == ring[-2:]
    o._telemetry = None


# ------------------------------------------- hist_quantile interpolation

def test_hist_quantile_interpolates_within_bucket():
    h = Histogram()
    for _ in range(50):
        h.observe(3.0)
    for _ in range(50):
        h.observe(3.5)
    d = h.as_dict()
    # all values share the (2, 4] bucket; the old estimator returned
    # the bucket's upper bound (4.0) for every quantile
    p50 = hist_quantile(d, 0.5)
    assert 3.0 <= p50 <= 3.5          # clamped to observed [min, max]
    assert p50 < 4.0
    # monotone in q
    qs = [hist_quantile(d, q) for q in (0.5, 0.9, 0.99)]
    assert qs == sorted(qs)
    # the "0" bucket holds only <= 0 values
    z = Histogram()
    z.observe(0.0)
    z.observe(-1.0)
    assert hist_quantile(z.as_dict(), 0.99) == 0.0
    # empty / malformed -> None, never a crash
    assert hist_quantile(Histogram().as_dict(), 0.5) is None
    assert hist_quantile({"count": "x"}, 0.5) is None
    assert hist_quantile("nope", 0.5) is None


def test_hist_quantile_error_bounded_by_bucket_width():
    """The estimate and the exact rank quantile share the winning log2
    bucket, so |est - exact| is bounded by that bucket's width."""
    import math

    rng = random.Random(20)
    vals = [rng.uniform(0.001, 900.0) for _ in range(500)]
    h = Histogram()
    for v in vals:
        h.observe(v)
    d = h.as_dict()
    s = sorted(vals)
    for q in (0.5, 0.9, 0.99):
        est = hist_quantile(d, q)
        exact = s[max(1, math.ceil(q * len(s))) - 1]
        hi = float(2 ** max(0, math.ceil(math.log2(exact))))
        width = hi - (hi / 2.0 if hi >= 2.0 else 0.0)
        assert abs(est - exact) <= width + 1e-9, (q, est, exact, width)


# --------------------------------------- epoch re-basing: ingest + merge

def test_export_ingest_negative_epoch_delta_clamps(tmp_path):
    """A worker whose monotonic epoch PREDATES the coordinator's (it
    booted first) re-bases to a negative delta: events from before the
    coordinator's epoch clamp to ts 0 instead of going negative (the
    Chrome-trace schema and the validator both require ts >= 0)."""
    coord = Tracer()
    worker = Tracer()
    worker.pid = coord.pid + 1
    worker.role = "worker_old"
    worker._t0 = coord.t0_ns - 3_000_000       # worker booted 3ms earlier
    worker.add_complete("early", worker.t0_ns,
                        worker.t0_ns + 1_000)  # before coord's epoch
    worker.add_complete("late", worker.t0_ns + 5_000_000,
                        worker.t0_ns + 5_001_000)
    ship = worker.export(max_events=10)
    assert coord.ingest(ship) == 2
    doc = coord.to_dict()
    by_name = {e["name"]: e for e in doc["traceEvents"]
               if e.get("name") in ("early", "late")}
    assert by_name["early"]["ts"] == 0         # clamped, not negative
    assert by_name["late"]["ts"] == 2000       # -3ms + 5ms = +2ms in µs
    path = tmp_path / "clamped.json"
    path.write_text(json.dumps(doc))
    assert obs_cli.main(["--validate", str(path)]) == 0


def test_cli_merge_worker_epoch_predating_coordinator(tmp_path):
    """merge re-bases onto the OLDEST known epoch, so a worker that
    booted before the coordinator keeps its early events at small
    positive ts and the coordinator's events shift right."""
    a = Tracer()
    a.role = "coordinator"
    a.add_instant("coord.mark")
    b = Tracer()
    b.pid = a.pid + 1
    b.role = "worker0"
    b._t0 = a.t0_ns - 5_000_000                # worker epoch 5ms earlier
    b.add_complete("distrib.chunk", b.t0_ns, b.t0_ns + 1000)
    pa, pb = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    a.write(pa)
    b.write(pb)
    merged = str(tmp_path / "m.json")
    assert obs_cli.main(["merge", "--out", merged, pa, pb]) == 0
    assert obs_cli.main(["--validate", merged]) == 0
    doc = json.load(open(merged))
    chunk = [e for e in doc["traceEvents"]
             if e.get("name") == "distrib.chunk"][0]
    mark = [e for e in doc["traceEvents"]
            if e.get("name") == "coord.mark"][0]
    assert chunk["ts"] == 0                    # worker owns the base epoch
    assert mark["ts"] >= 5000                  # coordinator shifted +5ms


def test_cli_merge_doc_without_epoch_keeps_own_timebase(tmp_path):
    """A trace doc with no epoch stamp (foreign/hand-built) cannot be
    re-based: merge keeps its own timebase instead of guessing."""
    a = Tracer()
    a.role = "coordinator"
    a.add_instant("coord.mark")
    pa = str(tmp_path / "a.json")
    a.write(pa)
    bare = {
        "traceEvents": [
            {"name": "foreign.span", "ph": "X", "ts": 7, "dur": 3,
             "pid": 999, "tid": 1, "cat": "racon_tpu", "args": {}},
        ],
        "displayTimeUnit": "ms",
    }
    pb = str(tmp_path / "bare.json")
    json.dump(bare, open(pb, "w"))
    merged = str(tmp_path / "m.json")
    assert obs_cli.main(["merge", "--out", merged, pa, pb]) == 0
    doc = json.load(open(merged))
    foreign = [e for e in doc["traceEvents"]
               if e.get("name") == "foreign.span"][0]
    assert foreign["ts"] == 7                  # untouched: no epoch known
