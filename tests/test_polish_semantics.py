"""Polish-phase semantics: drop-unpolished behavior and the opt-in device
aligner phase (reference behaviors: src/polisher.cpp:520-527 emit rule;
cuda aligner claiming src/cuda/cudapolisher.cpp:74-214)."""

import random

import pytest

import racon_tpu
from racon_tpu import native


def _dataset(tmp_path, rng, with_orphan_target=True):
    """Two targets; the second gets no overlaps (stays unpolished)."""
    t0 = "".join(rng.choice("ACGT") for _ in range(300))
    t1 = "".join(rng.choice("ACGT") for _ in range(250))
    with open(tmp_path / "targets.fasta", "w") as f:
        f.write(f">t0\n{t0}\n")
        if with_orphan_target:
            f.write(f">t1\n{t1}\n")
    with open(tmp_path / "reads.fasta", "w") as rf, \
            open(tmp_path / "ovl.paf", "w") as of:
        for i in range(4):
            rf.write(f">r{i}\n{t0}\n")
            of.write(f"r{i}\t{len(t0)}\t0\t{len(t0)}\t+\tt0\t{len(t0)}\t0\t"
                     f"{len(t0)}\t{len(t0)}\t{len(t0)}\t60\n")
    return t0, t1


def test_drop_unpolished_default(tmp_path):
    rng = random.Random(2)
    t0, _ = _dataset(tmp_path, rng)
    p = racon_tpu.CpuPolisher(str(tmp_path / "reads.fasta"),
                              str(tmp_path / "ovl.paf"),
                              str(tmp_path / "targets.fasta"),
                              window_length=100, match=5, mismatch=-4,
                              gap=-8)
    p.initialize()
    res = p.polish(True)
    # only the covered target survives
    assert [n.split()[0] for n, _ in res] == ["t0"]
    assert res[0][1] == t0


def test_include_unpolished(tmp_path):
    rng = random.Random(2)
    t0, t1 = _dataset(tmp_path, rng)
    p = racon_tpu.CpuPolisher(str(tmp_path / "reads.fasta"),
                              str(tmp_path / "ovl.paf"),
                              str(tmp_path / "targets.fasta"),
                              window_length=100, match=5, mismatch=-4,
                              gap=-8)
    p.initialize()
    res = p.polish(False)
    names = [n.split()[0] for n, _ in res]
    assert names == ["t0", "t1"]
    assert res[1][1] == t1  # orphan target passes through unmodified


def test_no_trimming_keeps_low_coverage_ends(tmp_path):
    """--no-trimming analogue: TGS trim off must never shorten consensus
    below the trimmed variant (reference: src/window.cpp:125-146 gated by
    the trim flag, src/main.cpp:24)."""
    import os

    from tests.conftest import DATA
    if not os.path.isdir(DATA):
        import pytest
        pytest.skip("lambda data unavailable")

    def run(trim):
        p = racon_tpu.CpuPolisher(DATA + "sample_reads.fastq.gz",
                                  DATA + "sample_overlaps.sam.gz",
                                  DATA + "sample_layout.fasta.gz",
                                  trim=trim, match=5, mismatch=-4, gap=-8)
        p.initialize()
        return p.polish(True)

    trimmed = run(True)[0][1]
    untrimmed = run(False)[0][1]
    assert len(untrimmed) > len(trimmed)


def test_device_aligner_phase_opt_in(tmp_path, monkeypatch):
    """RACON_TPU_DEVICE_ALIGNER=1 serves PAF overlaps on the device
    aligner; result equals the host-aligned run."""
    rng = random.Random(4)
    truth = "".join(rng.choice("ACGT") for _ in range(400))

    def mutate(s, rate):
        out = []
        for c in s:
            r = rng.random()
            if r < rate / 2:
                out.append(rng.choice("ACGT"))
            elif r < rate:
                continue
            else:
                out.append(c)
        return "".join(out)

    draft = mutate(truth, 0.02)
    reads = [mutate(truth, 0.05) for _ in range(5)]
    with open(tmp_path / "t.fasta", "w") as f:
        f.write(f">t\n{draft}\n")
    with open(tmp_path / "r.fasta", "w") as rf, \
            open(tmp_path / "o.paf", "w") as of:
        for i, r in enumerate(reads):
            rf.write(f">r{i}\n{r}\n")
            of.write(f"r{i}\t{len(r)}\t0\t{len(r)}\t+\tt\t{len(draft)}\t0\t"
                     f"{len(draft)}\t{min(len(r), len(draft))}\t"
                     f"{max(len(r), len(draft))}\t60\n")

    def run(device):
        monkeypatch.setenv("RACON_TPU_DEVICE_ALIGNER",
                           "1" if device else "0")
        p = racon_tpu.TpuPolisher(str(tmp_path / "r.fasta"),
                                  str(tmp_path / "o.paf"),
                                  str(tmp_path / "t.fasta"),
                                  window_length=100, match=5, mismatch=-4,
                                  gap=-8)
        p.initialize()
        return p.polish(True)

    dev = run(True)
    host = run(False)
    assert len(dev) == len(host) == 1
    # Equally-optimal alignments may break ties differently; consensus must
    # stay within a pinned sliver of each other and near the truth.
    d = native.edit_distance(dev[0][1].encode(), host[0][1].encode())
    assert d <= 2, d
    assert native.edit_distance(dev[0][1].encode(), truth.encode()) <= 8
