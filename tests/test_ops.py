"""Device kernel tests (JAX CPU backend, 8 virtual devices): differential
against the host oracle, the way the reference pins GPU results against CPU
results (/root/reference/test/racon_test.cpp:297-507)."""

import random

import numpy as np
import pytest

from racon_tpu import native
from racon_tpu.ops import align, poa
from racon_tpu.ops.encoding import decode, encode


def mutate(seq, rate, rng):
    out = bytearray()
    for c in seq:
        r = rng.random()
        if r < rate / 3:
            out.append(rng.choice(b"ACGT"))
        elif r < 2 * rate / 3:
            pass
        elif r < rate:
            out.append(c)
            out.append(rng.choice(b"ACGT"))
        else:
            out.append(c)
    return bytes(out)


@pytest.fixture(scope="module")
def poa_kernel():
    cfg = poa.PoaConfig(max_nodes=768, max_len=384, max_backbone=256,
                        max_edges=12, depth=16, match=5, mismatch=-4, gap=-8)
    return cfg, poa.build_poa_kernel(cfg)


def run_device_window(cfg, kernel, backbone, layers, begins, ends,
                      quals=None):
    B = 1
    bl = len(backbone)
    bb = np.zeros((B, cfg.max_backbone), np.uint8)
    bb[0, :bl] = encode(np.frombuffer(backbone, np.uint8))
    bbw = np.zeros((B, cfg.max_backbone), np.int32)
    bb_len = np.array([bl], np.int32)
    nl = np.array([len(layers)], np.int32)
    seqs = np.zeros((B, cfg.depth, cfg.max_len), np.uint8)
    ws = np.zeros((B, cfg.depth, cfg.max_len), np.int32)
    lens = np.zeros((B, cfg.depth), np.int32)
    bg = np.zeros((B, cfg.depth), np.int32)
    en = np.zeros((B, cfg.depth), np.int32)
    for i, l in enumerate(layers):
        seqs[0, i, :len(l)] = encode(np.frombuffer(l, np.uint8))
        if quals is not None:
            ws[0, i, :len(l)] = (
                np.frombuffer(quals[i], np.uint8).astype(np.int32) - 33)
        else:
            ws[0, i, :len(l)] = 1
        lens[0, i] = len(l)
        bg[0, i] = begins[i]
        en[0, i] = ends[i]
    cb, cc, cl, failed, _ = (np.asarray(x)
                             for x in kernel(bb, bbw, bb_len, nl, seqs, ws,
                                             lens, bg, en))
    assert not failed[0]
    return decode(cb[0, :cl[0]]), cc[0, :cl[0]]


@pytest.mark.parametrize("seed", [0, 2, 3])
def test_device_poa_matches_host(poa_kernel, seed):
    cfg, kernel = poa_kernel
    rng = random.Random(seed)
    L = 200
    truth = bytes(rng.choice(b"ACGT") for _ in range(L))
    backbone = mutate(truth, 0.1, rng)
    bl = len(backbone)
    layers, begins, ends = [], [], []
    for _ in range(10):
        layers.append(mutate(truth, 0.12, rng))
        begins.append(0)
        ends.append(bl - 1)
    dev, _ = run_device_window(cfg, kernel, backbone, layers, begins, ends)
    host, _ = native.window_consensus(backbone, layers, begins=begins,
                                      ends=ends, trim=False)
    # Exact match on most seeds; tie-breaks may differ by a base or two the
    # way the reference's CUDA path diverges from its CPU path.
    assert native.edit_distance(dev, host) <= 2
    assert native.edit_distance(dev, truth) <= native.edit_distance(
        host, truth) + 2


def test_device_poa_partial_layers_and_quality(poa_kernel):
    cfg, kernel = poa_kernel
    rng = random.Random(42)
    L = 200
    truth = bytes(rng.choice(b"ACGT") for _ in range(L))
    backbone = mutate(truth, 0.08, rng)
    bl = len(backbone)
    layers, begins, ends, quals = [], [], [], []
    for _ in range(12):
        if rng.random() < 0.6:
            b = rng.randint(0, L // 2)
            e = rng.randint(b + L // 4, L - 1)
        else:
            b, e = 0, L - 1
        seg = mutate(truth[b:e + 1], 0.12, rng)
        layers.append(seg)
        begins.append(min(b, bl - 1))
        ends.append(min(e, bl - 1))
        quals.append(bytes(33 + rng.randint(5, 40) for _ in seg))
    order = sorted(range(len(layers)), key=lambda i: begins[i])
    layers = [layers[i] for i in order]
    begins = [begins[i] for i in order]
    ends = [ends[i] for i in order]
    quals = [quals[i] for i in order]

    dev, cov = run_device_window(cfg, kernel, backbone, layers, begins, ends,
                                 quals=quals)
    host, _ = native.window_consensus(backbone, layers, quals=quals,
                                      begins=begins, ends=ends, trim=False)
    assert native.edit_distance(dev, host) <= 2
    assert len(cov) == len(dev)


def test_device_aligner_optimal():
    rng = random.Random(9)
    pairs = []
    for _ in range(6):
        L = rng.randint(150, 1500)
        t = bytes(rng.choice(b"ACGT") for _ in range(L))
        q = mutate(t, rng.choice([0.05, 0.2]), rng)
        pairs.append((q, t))

    class FakePipe:
        def __init__(self, pairs):
            self.pairs = pairs
            self.cigars = {}

        def align_job(self, i):
            q, t = self.pairs[i]
            return (np.frombuffer(q, np.uint8), np.frombuffer(t, np.uint8))

        def set_job_cigar(self, i, c):
            self.cigars[i] = c

    pipe = FakePipe(pairs)
    served = align.run_jobs(pipe, list(range(len(pairs))))
    assert served == len(pairs)
    for i, (q, t) in enumerate(pairs):
        cigar = pipe.cigars[i]
        cost = qi = ti = 0
        num = ""
        for ch in cigar:
            if ch.isdigit():
                num += ch
                continue
            k = int(num)
            num = ""
            if ch == "M":
                for _ in range(k):
                    cost += q[qi] != t[ti]
                    qi += 1
                    ti += 1
            elif ch == "I":
                cost += k
                qi += k
            elif ch == "D":
                cost += k
                ti += k
        assert (qi, ti) == (len(q), len(t))
        assert cost == native.edit_distance(q, t)


def test_ops_to_cigar():
    assert align.ops_to_cigar(np.array([], np.uint8)) == ""
    assert align.ops_to_cigar(np.array([0, 0, 1, 2, 2], np.uint8)) == "2M1I2D"


def test_device_eligible():
    assert align.device_eligible(1000, 1000)
    assert not align.device_eligible(0, 100)
    assert not align.device_eligible(100, 9000)
    assert not align.device_eligible(100, 1000)  # length gap exceeds band
