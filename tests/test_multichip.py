"""Multi-device sharding: the polish step must compile and run sharded over
an 8-device mesh (virtual CPU devices in CI; ICI on real hardware)."""

import jax
import numpy as np
import pytest


def test_eight_virtual_devices_available():
    assert len(jax.devices()) == 8


def test_dryrun_multichip_8():
    import __graft_entry__
    __graft_entry__.dryrun_multichip(8)


def test_dryrun_distrib_two_process_byte_identity():
    """The distrib rung of the multichip gate standalone: a 2-process
    localhost fleet must gather byte-identically to the oracle."""
    import __graft_entry__
    note = __graft_entry__.dryrun_distrib(2)
    assert "byte-identical" in note


def test_entry_compiles():
    import __graft_entry__
    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    cons_len = np.asarray(out[2])
    assert (cons_len > 0).all()


def test_multichip_sweep_measures_in_process():
    """The sweep's worker body (tools/multichip.measure) on the ambient
    8-device mesh: sharded geometry, positive throughput, balanced
    per-device row counters."""
    import os

    from racon_tpu.tools import multichip as mc

    os.environ["RACON_TPU_BATCH_WINDOWS"] = "16"
    try:
        entry = mc.measure(8, repeats=1)
    finally:
        os.environ.pop("RACON_TPU_BATCH_WINDOWS", None)
    assert entry["shards"] == 8 and entry["batch"] == 16
    assert entry["rows_per_device"] == 2
    assert entry["windows_per_s"] > 0
    rows = [v for k, v in entry["counters"].items()
            if k.startswith("shard.rows.d")]
    assert len(rows) == 8 and max(rows) == min(rows)


def test_bench_multichip_entry_normalizes_as_fixed_point():
    """The multichip bench entry must round-trip normalize_entry
    unchanged and form its own bench-history series (profile
    multichip-*), like the serve and distrib lanes."""
    import os
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    try:
        from bench import normalize_entry
    finally:
        sys.path.remove(root)
    from racon_tpu.obs import bench_track

    entry = {
        "metric": "multichip: sharded consensus windows/sec at 8 "
                  "device(s) (counts [1, 2, 4, 8], tier xla, batch 64) "
                  "[FORCED DRY-RUN: not device evidence]",
        "value": 105.2, "unit": "windows/s", "vs_baseline": None,
        "cost_model": None, "pack_split": None, "serial_steps": None,
        "cells_banded": None, "band_hit_rate": None,
        "peak_rss_mb": None, "budget_mb": None,
        "multichip": {"counts": {"1": {"windows_per_s": 95.1, "ok": True},
                                 "8": {"windows_per_s": 105.2, "ok": True}},
                      "scaling_vs_1": 1.106},
        "forced": True,
        "mbp": 0.5, "input": "paf", "profile": "multichip-ont",
    }
    assert normalize_entry(dict(entry)) == entry
    plain = dict(entry, profile="ont")
    assert (bench_track.series_key(entry)
            != bench_track.series_key(plain))
