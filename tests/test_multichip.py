"""Multi-device sharding: the polish step must compile and run sharded over
an 8-device mesh (virtual CPU devices in CI; ICI on real hardware)."""

import jax
import numpy as np
import pytest


def test_eight_virtual_devices_available():
    assert len(jax.devices()) == 8


def test_dryrun_multichip_8():
    import __graft_entry__
    __graft_entry__.dryrun_multichip(8)


def test_dryrun_distrib_two_process_byte_identity():
    """The distrib rung of the multichip gate standalone: a 2-process
    localhost fleet must gather byte-identically to the oracle."""
    import __graft_entry__
    note = __graft_entry__.dryrun_distrib(2)
    assert "byte-identical" in note


def test_entry_compiles():
    import __graft_entry__
    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    cons_len = np.asarray(out[2])
    assert (cons_len > 0).all()
